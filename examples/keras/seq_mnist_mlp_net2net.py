#!/usr/bin/env python
"""Net2Net MLP teacher→student with the Sequential API (reference:
examples/python/keras/seq_mnist_mlp_net2net.py): Sequential teacher
trains, its Dense layers hand their trained weights to a Sequential
student via get_weights/set_weights across two compiled models."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from dlrm_flexflow_tpu import keras as K
from dlrm_flexflow_tpu.keras.datasets import mnist


def main():
    (x_train, y_train), _ = mnist.load_data()
    x_train = x_train.reshape(len(x_train), 784).astype(np.float32) / 255.0
    y_train = y_train.reshape(-1, 1).astype(np.int32)

    d1 = K.Dense(256, activation="relu", input_shape=(784,))
    d2 = K.Dense(10)
    teacher = K.Sequential([d1, d2, K.Activation("softmax")])
    teacher.compile(optimizer=K.SGD(learning_rate=0.05),
                    loss="sparse_categorical_crossentropy",
                    metrics=["accuracy"])
    teacher.fit(x_train, y_train, batch_size=64, epochs=2)

    d1_k, d1_b = d1.get_weights(teacher.ffmodel)
    d2_k, d2_b = d2.get_weights(teacher.ffmodel)

    sd1 = K.Dense(256, activation="relu", input_shape=(784,))
    sd2 = K.Dense(10)
    student = K.Sequential([sd1, sd2, K.Activation("softmax")])
    student.compile(optimizer=K.SGD(learning_rate=0.05),
                    loss="sparse_categorical_crossentropy",
                    metrics=["accuracy"])
    sd1.set_weights(student.ffmodel, d1_k, d1_b)
    sd2.set_weights(student.ffmodel, d2_k, d2_b)

    cb = K.VerifyMetrics(metric="accuracy", threshold=0.6)
    student.fit(x_train, y_train, batch_size=64, epochs=4, callbacks=[cb])


if __name__ == "__main__":
    main()
