#!/bin/bash
# Reference run_random.sh:1-10 shapes: batch 256/device, 8 x 1M-row x 64-d
# embedding tables, bot MLP 64-512-512-64, top MLP 576-1024-1024-1024-1.
ndev=${NDEV:-$(python -c 'import jax; print(len(jax.devices()))')}
python "$(dirname "$0")/dlrm.py" \
    -ll:gpu "$ndev" -b $((256 * ndev)) -e 1 \
    --arch-embedding-size 1000000-1000000-1000000-1000000-1000000-1000000-1000000-1000000 \
    --arch-sparse-feature-size 64 \
    --arch-mlp-bot 64-512-512-64 \
    --arch-mlp-top 576-1024-1024-1024-1 \
    "$@"
