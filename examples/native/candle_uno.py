#!/usr/bin/env python
"""CANDLE Uno multi-tower drug-response MLP on synthetic features
(reference: examples/cpp/candle_uno/candle_uno.cc:115-126 — per-feature
towers merged by concat into the top dense stack, MSE regression).

  python examples/native/candle_uno.py -b 64 -e 1
"""

import sys

import numpy as np

from _common import ff, setup, train
from dlrm_flexflow_tpu.models.candle_uno import build_candle_uno


def main(argv=None):
    cfg, mesh = setup(argv if argv is not None else sys.argv[1:])
    model = ff.FFModel(cfg)
    inputs, _ = build_candle_uno(model)
    n = 4 * cfg.batch_size
    r = np.random.RandomState(cfg.seed)
    x = {k: r.randn(n, d).astype(np.float32) for k, (_, d) in inputs.items()}
    y = r.rand(n, 1).astype(np.float32)  # growth in [0,1]
    train(model, x, y, cfg, loss="mean_squared_error", metrics=("mse",),
          mesh=mesh)


if __name__ == "__main__":
    main()
