"""Shared plumbing for native-API examples: path shim, flag parsing,
synthetic data, train loop (reference: each examples/cpp app's
top_level_task + DataLoader)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import dlrm_flexflow_tpu as ff  # noqa: E402
from dlrm_flexflow_tpu.parallel.mesh import make_mesh  # noqa: E402


def setup(argv, default_batch=64):
    """Parse reference-style flags; returns (FFConfig, mesh)."""
    # `JAX_PLATFORMS=cpu` alone is ignored where a sitecustomize pins an
    # accelerator plugin (the axon tunnel does); tests and CPU-only runs
    # set FF_FORCE_CPU=<ndev> to virtualize host devices explicitly
    force_cpu = int(os.environ.get("FF_FORCE_CPU") or 0)
    if force_cpu > 0:
        from dlrm_flexflow_tpu.utils.testing import ensure_cpu_devices
        ensure_cpu_devices(force_cpu)
    import jax
    cfg = ff.FFConfig.parse_args(argv)
    if cfg.batch_size <= 0:
        cfg.batch_size = default_batch
    ndev = min(cfg.num_devices, len(jax.devices())) or 1
    return cfg, make_mesh(num_devices=ndev)


def synthetic_classification(inputs, num_classes, n, seed=0):
    """Random images/features + int labels for each named input."""
    r = np.random.RandomState(seed)
    x = {name: r.randn(n, *shape[1:]).astype(np.float32)
         for name, shape in inputs.items()}
    y = r.randint(0, num_classes, size=(n, 1)).astype(np.int32)
    return x, y


def train(model, inputs, labels, cfg, loss="sparse_categorical_crossentropy",
          metrics=("accuracy",), optimizer=None, mesh=None, strategies=None):
    model.compile(optimizer or ff.SGDOptimizer(lr=cfg.learning_rate), loss,
                  list(metrics), mesh=mesh, strategies=strategies)
    model.init_layers(seed=cfg.seed)
    return model.fit(inputs, labels, epochs=cfg.epochs)
