#!/usr/bin/env python
"""DLRM strategy generator — reference dlrm_strategy.py / gen_strategy.sh /
dlrm_strategy_hetero.cc parity.

The reference generates stand-alone C++ binaries that emit protobuf strategy
files (src/runtime/dlrm_strategy.py writes dlrm_strategy.cc; gen_strategy.sh
builds+runs it; dlrm_strategy_hetero.cc is the CPU-embedding variant). Here
the generator writes the same proto2 wire format directly
(parallel/strategy_io.py) with the same op-key scheme:

- "embedding{i}"  i < num_emb : dims (1,1) — whole table — round-robin
  device_ids[i % num_devices]; DeviceType CPU when --hetero (host offload,
  dlrm_strategy_hetero.cc:28-36).
- "linear", "mse_loss", "concat": data-parallel over all devices (reference
  writes Legion-order dims [1, D]; the codec handles the reversal).

The emitted files load through FFModel.compile(--import ...) on this
framework AND parse with the reference's proto2 schema — and the reference's
own prebuilt .pb files load here, via the generic-key resolution in
FFModel._resolve_generic_strategy_keys.

Usage:
  python gen_strategy.py -g 8 -e 8                 # dlrm_strategy_8embs_8gpus.pb
  python gen_strategy.py -g 1 -e 8 --hetero -c 1   # dlrm_strategy_8nEmb_1cpu_1gpu.pb
  python gen_strategy.py -g 8 -e 16 -o out.pb
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from dlrm_flexflow_tpu.parallel.pconfig import ParallelConfig
from dlrm_flexflow_tpu.parallel.strategy_io import save_strategies


def build_strategy(num_devices: int, num_emb: int, hetero: bool = False,
                   num_cpus: int = 1):
    """Reference dlrm_strategy.cc:242-296 semantics: embeddings round-robin
    one-whole-table-per-device; linear/mse_loss/concat data-parallel."""
    strategies = {}
    for i in range(num_emb):
        if hetero:
            strategies[f"embedding{i}"] = ParallelConfig(
                (1, 1), device_type="CPU", device_ids=(i % max(num_cpus, 1),))
        else:
            strategies[f"embedding{i}"] = ParallelConfig(
                (1, 1), device_ids=(i % num_devices,))
    dp = ParallelConfig((num_devices, 1),
                        device_ids=tuple(range(num_devices)))
    for name in ("linear", "mse_loss", "concat"):
        strategies[name] = dp
    return strategies


def main():
    p = argparse.ArgumentParser()
    p.add_argument("-g", "--num-gpu", "--num-devices", dest="num_devices",
                   type=int, default=8, help="number of TPU chips")
    p.add_argument("-e", "--num-emb", type=int, default=8,
                   help="number of embedding tables")
    p.add_argument("-c", "--num-cpus", type=int, default=1,
                   help="hetero: number of host (CPU) workers")
    p.add_argument("--hetero", action="store_true",
                   help="place embeddings on host CPUs "
                        "(dlrm_strategy_hetero.cc)")
    p.add_argument("-o", "--output", default=None,
                   help="output path (.pb or .json); default uses the "
                        "reference naming scheme")
    opts = p.parse_args()

    out = opts.output
    if out is None:
        if opts.hetero:
            out = (f"dlrm_strategy_{opts.num_emb}nEmb_{opts.num_cpus}cpu_"
                   f"{opts.num_devices}gpu.pb")
        else:
            out = f"dlrm_strategy_{opts.num_emb}embs_{opts.num_devices}gpus.pb"
    s = build_strategy(opts.num_devices, opts.num_emb, opts.hetero,
                       opts.num_cpus)
    save_strategies(out, s)
    print("Created " + out)


if __name__ == "__main__":
    main()
