#!/usr/bin/env python
"""Criteo → HDF5 preprocessing for the DLRM app.

Parity with the reference preprocessor (reference:
examples/cpp/DLRM/preprocess_hdf.py): converts a preprocessed Criteo
`.npz` (keys X_cat/X_int/y, the output of facebook dlrm's
data_utils.getCriteoAdData) into the HDF5 layout the DLRM data loader
reads (datasets X_cat int64, X_int float32 log-transformed, y float32 —
reference dlrm.cc:266-382 probes exactly these).

Also accepts raw Criteo Kaggle TSV (label + 13 int + 26 hex-categorical
columns per line) so the whole pipeline runs without the torch-side
preprocessing: integers are log1p'd, categoricals are hashed into
`--hash-size` buckets per feature (the modulus trick the DLRM paper uses).

Usage:
  python preprocess_hdf.py -i kaggle_processed.npz -o train.h5
  python preprocess_hdf.py -i train.txt -o train.h5 --hash-size 100000
"""

import argparse

import numpy as np


def convert_npz(path: str):
    """Reference behavior: X_cat→int64, X_int→log(x+1) float32, y→float32."""
    data = np.load(path)
    x_cat = data["X_cat"].astype(np.int64)
    # clamp negatives before the log transform (Criteo int features go
    # below -1; log(x+1) would produce NaN)
    x_int = np.log(np.maximum(data["X_int"].astype(np.float32), 0.0) + 1)
    y = data["y"].astype(np.float32)
    return x_int, x_cat, y


def convert_tsv(path: str, hash_size: int, num_int: int = 13,
                num_cat: int = 26, chunk_rows: int = 1_000_000):
    """Raw Criteo Kaggle TSV: label \\t 13 ints \\t 26 hex cats.

    Parses in fixed-size chunks into numpy buffers (the Kaggle train.txt is
    ~45M rows; per-row Python lists would not fit in memory)."""
    int_chunks, cat_chunks, y_chunks = [], [], []
    ints = np.zeros((chunk_rows, num_int), np.float32)
    cats = np.zeros((chunk_rows, num_cat), np.int64)
    ys = np.zeros((chunk_rows,), np.float32)
    n = 0

    def flush():
        nonlocal n
        if n:
            int_chunks.append(ints[:n].copy())
            cat_chunks.append(cats[:n].copy())
            y_chunks.append(ys[:n].copy())
            n = 0

    with open(path) as f:
        for line in f:
            cols = line.rstrip("\n").split("\t")
            if len(cols) < 1 + num_int + num_cat:
                cols = cols + [""] * (1 + num_int + num_cat - len(cols))
            ys[n] = float(cols[0] or 0)
            for j, c in enumerate(cols[1:1 + num_int]):
                ints[n, j] = max(int(c), 0) if c else 0
            for j, c in enumerate(cols[1 + num_int:1 + num_int + num_cat]):
                cats[n, j] = int(c, 16) % hash_size if c else 0
            n += 1
            if n == chunk_rows:
                flush()
    flush()
    x_int = np.log(np.concatenate(int_chunks) + 1)
    x_cat = np.concatenate(cat_chunks)
    y = np.concatenate(y_chunks)
    return x_int, x_cat, y


def write_hdf5(path: str, x_int, x_cat, y):
    import h5py
    with h5py.File(path, "w") as hdf:
        hdf.create_dataset("X_cat", data=x_cat)
        hdf.create_dataset("X_int", data=x_int)
        hdf.create_dataset("y", data=y)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-i", "--input", required=True,
                        help="input .npz (X_cat/X_int/y) or raw Criteo .tsv")
    parser.add_argument("-o", "--output", required=True,
                        help="output HDF file")
    parser.add_argument("--hash-size", type=int, default=10_000_000,
                        help="per-feature hash buckets for raw TSV input")
    args = parser.parse_args()

    if args.input.endswith(".npz"):
        x_int, x_cat, y = convert_npz(args.input)
    else:
        x_int, x_cat, y = convert_tsv(args.input, args.hash_size)
    write_hdf5(args.output, x_int, x_cat, y)
    print(f"wrote {args.output}: X_int {x_int.shape} X_cat {x_cat.shape} "
          f"y {y.shape}")


if __name__ == "__main__":
    main()
