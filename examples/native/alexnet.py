#!/usr/bin/env python
"""AlexNet on synthetic images (reference: examples/cpp/AlexNet/alexnet.cc
and examples/python/native/alexnet.py:7-70).

  python examples/native/alexnet.py -b 64 -e 1 [--image-hw 224]
"""

import sys

from _common import ff, setup, synthetic_classification, train
from dlrm_flexflow_tpu.models.alexnet import build_alexnet


def main(argv=None):
    cfg, mesh = setup(argv if argv is not None else sys.argv[1:])
    hw = 224
    if "--image-hw" in cfg.unparsed:
        hw = int(cfg.unparsed[cfg.unparsed.index("--image-hw") + 1])
    num_classes = 1000 if hw >= 128 else 10

    model = ff.FFModel(cfg)
    inputs, _ = build_alexnet(model, num_classes=num_classes, image_hw=hw)
    x, y = synthetic_classification(inputs, num_classes,
                                    4 * cfg.batch_size, seed=cfg.seed)
    train(model, x, y, cfg, mesh=mesh)


if __name__ == "__main__":
    main()
