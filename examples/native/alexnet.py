#!/usr/bin/env python
"""AlexNet on synthetic or ON-DISK images (reference:
examples/cpp/AlexNet/alexnet.cc and examples/python/native/alexnet.py:7-70;
the on-disk path is the ImgDataLoader4D parity,
python/flexflow_dataloader.cc).

  python examples/native/alexnet.py -b 64 -e 1 [--image-hw 224]
  python examples/native/alexnet.py --data-path imgs.ffbin  # or .npz/.npy
"""

import sys

from _common import ff, setup, synthetic_classification, train
from dlrm_flexflow_tpu.models.alexnet import build_alexnet


def main(argv=None):
    cfg, mesh = setup(argv if argv is not None else sys.argv[1:])
    hw = 224
    if "--image-hw" in cfg.unparsed:
        hw = int(cfg.unparsed[cfg.unparsed.index("--image-hw") + 1])
    num_classes = 1000 if hw >= 128 else 10
    data_path = None
    if "--data-path" in cfg.unparsed:
        data_path = cfg.unparsed[cfg.unparsed.index("--data-path") + 1]

    model = ff.FFModel(cfg)
    inputs, _ = build_alexnet(model, num_classes=num_classes, image_hw=hw)
    if data_path:
        import time

        from dlrm_flexflow_tpu.data import ImgDataLoader4D
        model.compile(ff.SGDOptimizer(lr=cfg.learning_rate),
                      "sparse_categorical_crossentropy", ["accuracy"],
                      mesh=mesh)
        model.init_layers()
        loader = ImgDataLoader4D(model, data_path,
                                 image_shape=inputs["image"][1:])
        model.train_batch_device(loader.next_batch())  # warm/compile
        t0 = time.time()
        steps = 0
        mets = None
        for _epoch in range(cfg.epochs):
            for _ in range(loader.num_batches):
                mets = model.train_batch_device(loader.next_batch())
                steps += 1
        loss = float(mets["loss"])
        dt = time.time() - t0
        print(f"[on-disk] loss={loss:.4f} "
              f"THROUGHPUT = {steps * cfg.batch_size / dt:.2f} samples/s")
        return
    x, y = synthetic_classification(inputs, num_classes,
                                    4 * cfg.batch_size, seed=cfg.seed)
    train(model, x, y, cfg, mesh=mesh)


if __name__ == "__main__":
    main()
