#!/bin/bash
# Multi-host DLRM launch (reference: examples/cpp/DLRM/run_summit.sh jsrun
# launch over GASNet; here every host runs the same SPMD program and JAX's
# distributed runtime carries cross-host traffic over DCN).
#
# On a Cloud TPU pod slice, run on EVERY worker (jax auto-detects the
# coordinator):
#   python examples/native/dlrm.py -b $((256 * NUM_CHIPS)) -e 2 \
#       --arch-embedding-size 1000000-...(8x) --arch-sparse-feature-size 64 \
#       --arch-mlp-bot 64-512-512-64 --arch-mlp-top 576-1024-1024-1024-1
#
# On a generic cluster, export on each host:
#   export COORDINATOR_ADDRESS=host0:1234 NUM_PROCESSES=4 PROCESS_ID=<rank>
# and call dlrm_flexflow_tpu.parallel.distributed.initialize_distributed()
# before building the model (dlrm.py does this when NUM_PROCESSES is set).
#
# This script demonstrates the 2-process form on one machine with CPU
# devices (smoke only):
set -e
cd "$(dirname "$0")/../.."
PIDS=()
trap '[ "${#PIDS[@]}" -gt 0 ] && kill "${PIDS[@]}" 2>/dev/null || true' EXIT
for RANK in 0 1; do
  COORDINATOR_ADDRESS=127.0.0.1:12355 NUM_PROCESSES=2 PROCESS_ID=$RANK \
  FF_CPU_DEVICES_PER_PROCESS=4 \
  python examples/native/dlrm.py -b 64 -e 1 \
      --arch-embedding-size 64-64-64-64 --arch-sparse-feature-size 8 \
      --arch-mlp-bot 4-16-8 --arch-mlp-top 40-16-1 &
  PIDS+=($!)
done
# argument-less `wait` would mask a crashed rank; collect every status so
# a failure still reaps the other rank (the EXIT trap kills stragglers)
STATUS=0
for PID in "${PIDS[@]}"; do wait "$PID" || STATUS=$?; done
exit $STATUS
