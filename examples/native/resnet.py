#!/usr/bin/env python
"""ResNet-{18,34,50,101} on synthetic images (reference:
examples/cpp/ResNet/resnet.cc).

  python examples/native/resnet.py -b 64 -e 1 --depth 50 [--image-hw 224]
"""

import sys

from _common import ff, setup, synthetic_classification, train
from dlrm_flexflow_tpu.models.resnet import build_resnet


def main(argv=None):
    cfg, mesh = setup(argv if argv is not None else sys.argv[1:])
    depth, hw = 50, 224
    u = cfg.unparsed
    if "--depth" in u:
        depth = int(u[u.index("--depth") + 1])
    if "--image-hw" in u:
        hw = int(u[u.index("--image-hw") + 1])
    num_classes = 1000 if hw >= 128 else 10

    model = ff.FFModel(cfg)
    inputs, _ = build_resnet(model, depth=depth, num_classes=num_classes,
                             image_hw=hw)
    x, y = synthetic_classification(inputs, num_classes,
                                    4 * cfg.batch_size, seed=cfg.seed)
    train(model, x, y, cfg, mesh=mesh)


if __name__ == "__main__":
    main()
