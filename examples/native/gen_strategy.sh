#!/bin/bash
# Regenerate the prebuilt DLRM strategy files under strategies/
# (reference: src/runtime/gen_strategy.sh builds+runs the generated C++
# emitters; here the python generator writes the wire format directly).
set -e
cd "$(dirname "$0")"
mkdir -p ../../strategies
python gen_strategy.py -g 8 -e 8 -o ../../strategies/dlrm_strategy_8embs_8gpus.pb
python gen_strategy.py -g 8 -e 16 -o ../../strategies/dlrm_strategy_16embs_8gpus.pb
python gen_strategy.py -g 16 -e 16 -o ../../strategies/dlrm_strategy_16embs_16gpus.pb
python gen_strategy.py -g 1 -e 8 --hetero -c 1 -o ../../strategies/dlrm_strategy_8nEmb_1cpu_1gpu.pb
