#!/usr/bin/env python
"""NMT LSTM seq2seq on synthetic token pairs (reference: nmt/nmt.cc:32
top_level_task — 2-layer 1024-wide encoder/decoder over reversed source,
per-position softmax; rebuilt as a model on the main framework rather
than a second runtime).

  python examples/native/nmt.py -b 16 -e 1 --seq-len 40
"""

import sys

import numpy as np

from _common import ff, setup, train
from dlrm_flexflow_tpu.models.nmt import build_nmt


def main(argv=None):
    cfg, mesh = setup(argv if argv is not None else sys.argv[1:],
                      default_batch=16)
    u = cfg.unparsed
    seq = int(u[u.index("--seq-len") + 1]) if "--seq-len" in u else 40
    vocab = int(u[u.index("--vocab") + 1]) if "--vocab" in u else 4096

    model = ff.FFModel(cfg)
    inputs, _ = build_nmt(model, src_vocab=vocab, tgt_vocab=vocab,
                          embed_dim=256, hidden=256, num_layers=2,
                          src_len=seq, tgt_len=seq)
    n = 2 * cfg.batch_size
    r = np.random.RandomState(cfg.seed)
    x = {k: r.randint(0, vocab, size=(n, seq)).astype(np.int32)
         for k in inputs}
    # next-token labels: one int per (batch, position), folded like logits
    y = r.randint(0, vocab, size=(n, seq)).astype(np.int32)
    train(model, x, y, cfg, loss="sparse_categorical_crossentropy",
          metrics=("accuracy",), mesh=mesh)


if __name__ == "__main__":
    main()
