#!/usr/bin/env python
"""DLRM training app (reference: examples/cpp/DLRM/dlrm.cc top_level_task
at :77 — arg parsing :84-96/:201-264, graph build :103-128, data loading
:266-589, train loop :166-187, throughput report :197-198).

Accepts the reference's flag spellings, e.g.:

  python examples/native/dlrm.py -ll:gpu 8 -b 2048 -e 2 \\
      --arch-embedding-size 1000000-1000000-1000000-1000000-1000000-1000000-1000000-1000000 \\
      --arch-sparse-feature-size 64 --arch-mlp-bot 64-512-512-64 \\
      --arch-mlp-top 576-1024-1024-1024-1 \\
      --budget 200 --export best.pb

Data: --data-path file.npz (dense/sparse/label arrays) or .ffbin
(data.dataloader.write_ffbin format); otherwise synthetic random like
run_random.sh.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import dlrm_flexflow_tpu as ff
from dlrm_flexflow_tpu.data.dataloader import FFBinDataLoader, SingleDataLoader
from dlrm_flexflow_tpu.models.dlrm import (DLRMConfig, build_dlrm,
                                           dlrm_strategy, synthetic_batch)
from dlrm_flexflow_tpu.parallel.mesh import make_mesh
from dlrm_flexflow_tpu.parallel.strategy_io import load_strategies
from dlrm_flexflow_tpu.search.mcmc import optimize
from dlrm_flexflow_tpu.utils.logging import get_logger

log_app = get_logger("dlrm")


def _check_sparse_bounds(sparse, dcfg):
    """Fail loudly when categorical indices exceed the configured table
    sizes: the embedding gather wraps indices modulo the table (silent row
    aliasing), so a --hash-size / --arch-embedding-size mismatch would
    otherwise train on wrong rows with a plausible-looking loss."""
    maxes = sparse.reshape(sparse.shape[0], sparse.shape[1], -1).max(
        axis=(0, 2))
    for t, (mx, rows) in enumerate(zip(maxes, dcfg.embedding_size)):
        if mx >= rows:
            raise ValueError(
                f"table {t}: max categorical index {int(mx)} >= configured "
                f"table size {rows}; regenerate the dataset with a matching "
                f"--hash-size or fix --arch-embedding-size")


def main(argv=None):
    if os.environ.get("NUM_PROCESSES") or os.environ.get(
            "COORDINATOR_ADDRESS"):
        # multi-host launch (reference run_summit.sh over GASNet)
        from dlrm_flexflow_tpu.parallel.distributed import \
            initialize_distributed
        initialize_distributed()
    cfg = ff.FFConfig.parse_args(argv)
    dcfg = DLRMConfig.parse_args(cfg.unparsed)
    data_path = None
    rest = cfg.unparsed
    if "--data-path" in rest:
        data_path = rest[rest.index("--data-path") + 1]

    import jax
    multiproc = jax.process_count() > 1
    if multiproc:
        # every rank runs this same SPMD program over the global mesh;
        # the process axis is the DCN axis (reference: one control-
        # replicated top_level_task per node, model.cc:1384-1409)
        from dlrm_flexflow_tpu.parallel.distributed import \
            make_multihost_mesh
        ndev = len(jax.devices())
        mesh = make_multihost_mesh()
    else:
        ndev = min(cfg.num_devices, len(jax.devices())) or len(jax.devices())
        mesh = make_mesh(num_devices=ndev)
    log_app.info("devices=%d processes=%d batch=%d tables=%d "
                 "zipf_alpha=%g", ndev,
                 jax.process_count(), cfg.batch_size,
                 len(dcfg.embedding_size), dcfg.zipf_alpha)

    model = ff.FFModel(cfg)
    build_dlrm(model, dcfg)

    # strategy: --import file > MCMC search (--budget) > hand-written DLRM
    if cfg.import_strategy_file:
        strategies = load_strategies(cfg.import_strategy_file)
        log_app.info("imported strategies from %s", cfg.import_strategy_file)
    elif cfg.search_budget > 0:
        # compile() exports the searched map when cfg.export_strategy_file
        # is set (--export), matching the reference's flow
        strategies = optimize(model, budget=cfg.search_budget,
                              alpha=cfg.search_alpha, ndev=ndev, verbose=True)
    else:
        strategies = dlrm_strategy(model, dcfg, ndev)

    model.compile(ff.SGDOptimizer(lr=cfg.learning_rate), "mean_squared_error",
                  ["mse"], mesh=mesh, strategies=strategies)
    model.init_layers()

    if data_path and data_path.endswith(".ffbin"):
        loader = FFBinDataLoader(model, data_path)
        num_batches = loader.num_batches
        next_batch = loader.next_batch
    elif data_path and (data_path.endswith(".h5")
                        or data_path.endswith(".hdf5")):
        # Criteo HDF5 from examples/native/preprocess_hdf.py (reference
        # dlrm.cc:266-382 reads the same X_int/X_cat/y layout)
        from dlrm_flexflow_tpu.data import load_dlrm_hdf5
        x, y = load_dlrm_hdf5(data_path)
        _check_sparse_bounds(x["sparse"], dcfg)
        loader = SingleDataLoader(model, x, y)
        num_batches = loader.num_batches
        next_batch = loader.next_batch
    elif data_path:
        d = np.load(data_path)
        _check_sparse_bounds(d["sparse"], dcfg)
        loader = SingleDataLoader(
            model, {"dense": d["dense"], "sparse": d["sparse"]}, d["label"])
        num_batches = loader.num_batches
        next_batch = loader.next_batch
    else:  # synthetic, like run_random.sh
        x, y = synthetic_batch(dcfg, cfg.batch_size)
        x["label"] = y
        if multiproc:
            # each rank contributes its host-local slice of the global
            # batch (reference: per-node zero-copy dataset residency,
            # dlrm.cc:384-484)
            from dlrm_flexflow_tpu.parallel.distributed import (
                global_batch_from_host_local, host_local_slice)
            staged = global_batch_from_host_local(host_local_slice(x), mesh)
        else:
            staged = model._device_batch(x)
        num_batches = 64
        next_batch = lambda: staged  # noqa: E731

    # warmup epoch compiles the jitted step (the reference warms its Legion
    # trace in epoch 0 before begin_trace, dlrm.cc:178-185)
    model.train_batch_device(next_batch())
    jax.block_until_ready(model.params)

    # fused supersteps (--superstep K / auto): the synthetic loop
    # dispatches K steps per host→device call, amortizing the dispatch
    # floor exactly like fit() does (loader-fed runs stay per-step here;
    # use fit() for the full staged/prefetched superstep pipeline)
    k_super = 1
    sstaged = None
    if not multiproc and data_path is None:
        k_super = model.resolve_superstep()
        k_super = k_super if k_super <= num_batches else 1
        if k_super > 1:
            from dlrm_flexflow_tpu.data.prefetch import stack_batches
            sstaged = model._stage_superstep(stack_batches([x] * k_super))
            model.train_batch_staged(sstaged)     # warm the fused exec
            jax.block_until_ready(model.params)

    if cfg.profiling:
        # per-op timing table (reference --profiling cudaEvent prints)
        from dlrm_flexflow_tpu.utils.profiling import (format_profile,
                                                       profile_ops)
        print(format_profile(profile_ops(model)))
    from dlrm_flexflow_tpu.utils.profiling import TraceContext
    # bound the number of in-flight async steps: XLA CPU's in-process
    # collectives can starve when many multi-device executions queue up on
    # few host cores; on real TPUs the device is the bottleneck, so a much
    # deeper pipeline is safe
    throttle = 1 if jax.default_backend() == "cpu" else 16
    t0 = time.time()
    step = 0
    with TraceContext(cfg.profile_dir or None):
        for _epoch in range(cfg.epochs):
            model.reset_metrics()
            b = 0
            while b < num_batches:
                if sstaged is not None and b + k_super <= num_batches:
                    mets = model.train_batch_staged(sstaged)
                    adv = k_super
                else:
                    mets = model.train_batch_device(next_batch())
                    adv = 1
                b += adv
                prev = step
                step += adv
                if step // throttle != prev // throttle:
                    jax.block_until_ready(mets["loss"])
        jax.block_until_ready(model.params)
    elapsed = time.time() - t0
    n_samples = cfg.epochs * num_batches * cfg.batch_size
    print(f"{model.perf.summary_line()}")
    print(f"ELAPSED TIME = {elapsed:.4f}s, THROUGHPUT = "
          f"{n_samples / elapsed:.2f} samples/s")


if __name__ == "__main__":
    main(sys.argv[1:])
