#!/usr/bin/env python
"""DLRM online serving app: dynamic-batched JSON inference over HTTP.

The read-path counterpart of examples/native/dlrm.py — the trainer
publishes rolling snapshots (fit(checkpoint_dir=...)); this app builds
the same graph, restores the newest snapshot params-only, and serves it
with the dynamic-batching engine (power-of-two bucket padding, AOT
warmup, bounded queue backpressure, per-request deadlines) while a
snapshot watcher hot-reloads newer checkpoints with zero downtime.

No framework webserver: a stdlib ``http.server`` ThreadingHTTPServer is
all the engine needs — every handler thread just submits into the
engine's queue and blocks on its future, the batcher coalesces across
handler threads.

  # terminal 1: train, publishing snapshots
  python examples/native/dlrm.py --checkpoint-dir /tmp/dlrm-ckpt --save-every 50

  # terminal 2: serve them, hot-reloading as they land
  python examples/native/serve_dlrm.py --checkpoint-dir /tmp/dlrm-ckpt \\
      --serve-max-batch 64 --serve-max-delay-ms 3 --port 8000

  curl -s localhost:8000/healthz
  curl -s localhost:8000/stats
  curl -s -X POST localhost:8000/predict -d \\
      '{"dense": [[0.1, 0.2, 0.3, 0.4]], "sparse": [[[1],[2],[3],[4]]]}'

Endpoints:
  POST /predict  {"dense": [...], "sparse": [...]}  ->
                 {"scores": [...], "version": N, "latency_ms": ...}
                 429 on Overloaded, 504 on DeadlineExceeded
  GET  /stats    engine stats() (p50/p99, batch fill, cache hit rate,
                 reloads, executable-cache occupancy)
  GET  /healthz  {"ok": true, "version": N}
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import dlrm_flexflow_tpu as ff
from dlrm_flexflow_tpu.models.dlrm import DLRMConfig, build_dlrm
from dlrm_flexflow_tpu.serve import DeadlineExceeded, Overloaded
from dlrm_flexflow_tpu.utils.logging import get_logger

log_app = get_logger("serve_dlrm")


def build_server_model(cfg, dcfg):
    """Same graph as the trainer (fingerprints must match for hot
    reload); compiled at the largest serve bucket so every bucket pads
    under the compile batch."""
    model = ff.FFModel(cfg)
    build_dlrm(model, dcfg)
    model.compile(ff.SGDOptimizer(lr=cfg.learning_rate),
                  "mean_squared_error", ["mse"])
    model.init_layers()
    return model


def make_handler(engine, input_names):
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        def _reply(self, code, payload):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):   # route through our logger
            log_app.debug(fmt, *args)

        def do_GET(self):
            if self.path == "/healthz":
                self._reply(200, {"ok": True, "version": engine.version})
            elif self.path == "/stats":
                self._reply(200, engine.stats())
            else:
                self._reply(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if self.path != "/predict":
                self._reply(404, {"error": f"no route {self.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                feats = {}
                for name in input_names:
                    if name not in req:
                        raise ValueError(f"missing input {name!r}")
                    arr = np.asarray(req[name])
                    feats[name] = (arr.astype(np.int32)
                                   if name == "sparse"
                                   else arr.astype(np.float32))
            except (ValueError, json.JSONDecodeError) as e:
                self._reply(400, {"error": str(e)})
                return
            try:
                pred = engine.predict(feats)
                self._reply(200, {
                    "scores": np.asarray(pred.scores).reshape(-1).tolist(),
                    "version": pred.version,
                    "latency_ms": round(pred.latency_ms, 3)})
            except Overloaded as e:
                self._reply(429, {"error": str(e)})
            except (DeadlineExceeded, TimeoutError) as e:
                self._reply(504, {"error": str(e)})
            except ValueError as e:
                self._reply(400, {"error": str(e)})

    return Handler


def main(argv=None):
    # same CPU-virtualization escape hatch as _common.setup (the axon
    # sitecustomize pins an accelerator plugin; FF_FORCE_CPU=<ndev>
    # virtualizes host devices explicitly for tests/CPU-only serving)
    force_cpu = int(os.environ.get("FF_FORCE_CPU") or 0)
    if force_cpu > 0:
        from dlrm_flexflow_tpu.utils.testing import ensure_cpu_devices
        ensure_cpu_devices(force_cpu)
    cfg = ff.FFConfig.parse_args(argv)
    dcfg = DLRMConfig.parse_args(cfg.unparsed)
    port = 8000
    rest = list(cfg.unparsed)
    if "--port" in rest:
        port = int(rest[rest.index("--port") + 1])

    model = build_server_model(cfg, dcfg)
    ckpt_dir = cfg.checkpoint_dir or None
    engine = ff.InferenceEngine(model, checkpoint_dir=ckpt_dir)
    if ckpt_dir:
        # initial load through the watcher's READ-ONLY manifest scan (a
        # CheckpointManager here would sweep tmp files under a live
        # trainer) — params_only restore of the newest valid snapshot
        if ff.SnapshotWatcher(engine, ckpt_dir).poll_once():
            log_app.info("serving snapshot version %d", engine.version)
        else:
            log_app.warning("no restorable snapshot in %s — serving "
                            "fresh init until the trainer publishes one",
                            ckpt_dir)
    input_names = [t.name for t in model.input_tensors]

    from http.server import ThreadingHTTPServer
    with engine:
        httpd = ThreadingHTTPServer(
            ("0.0.0.0", port), make_handler(engine, input_names))
        log_app.info("serving DLRM on :%d (buckets %s, max delay %.1f ms"
                     "%s)", port, engine.stats()["buckets"],
                     engine.config.max_delay_ms,
                     f", hot-reload from {ckpt_dir}" if ckpt_dir else "")
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            httpd.server_close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
