#!/usr/bin/env python
"""DLRM online serving app: dynamic-batched JSON inference over HTTP.

The read-path counterpart of examples/native/dlrm.py — the trainer
publishes rolling snapshots (fit(checkpoint_dir=...)); this app builds
the same graph, restores the newest snapshot params-only, and serves it
with the dynamic-batching engine (power-of-two bucket padding, AOT
warmup, bounded queue backpressure, per-request deadlines) while a
snapshot watcher hot-reloads newer checkpoints with zero downtime.

``--serve-replicas N`` turns the single engine into a FLEET: N replicas
(each compiled on its own slice of the local devices, data-parallel
params) behind a ``FleetRouter`` — queue-depth load balancing, a
circuit breaker that ejects and re-admits crashed replicas, bounded
retry with backoff, optional tail-latency hedging
(``--serve-hedge-ms``), and canary/shadow rollout knobs
(``--serve-canary-fraction``). Each replica follows the trainer's
snapshots independently (cross-mesh reshard is automatic in fleet
mode: per-device replicas consume the multi-device trainer's
checkpoints).

``--serve-shards N`` (host-table models) splits serving into a
SHARDED TIER: the engine/replicas become stateless rankers and the
embedding tables live once, row-sharded over N lookup shards
(``serve/shardtier.py``) — a model whose tables exceed one replica's
memory serves anyway. Responses then carry a per-shard version vector
and, while a shard is out, are served DEGRADED (cache hits + per-table
default rows, ``"degraded": true`` in the response and in /healthz —
still HTTP 200: degraded is not down, and a load balancer that treated
it as down would turn one dark shard into a full outage). Knobs:
``--serve-lookup-deadline-ms`` (per-fetch budget) and
``--serve-degrade {cache,fail}``.

``--serve-transport tcp --serve-shard-procs N`` moves the lookup tier
across a REAL process boundary: the app seeds the warm shard cache,
spawns N ``serve/shard_server.py`` OS processes (one slot each, wire
protocol over loopback TCP — ``serve/wire.py``), and the rankers
resolve ids through ``RemoteShard`` clients with per-request deadlines,
bounded retry/backoff, and CRC-checked frames. ``kill -9`` a shard
process and responses degrade (never fail) until the health loop
replaces it from the warm cache. Fault injection for drills:
``FF_FAULT_NET_DROP/DUP/REORDER/SLOW`` (see utils/faults.py). The
default ``--serve-transport inproc`` keeps today's in-process method
calls bit-for-bit.

``--retrieve on`` puts the RETRIEVAL CASCADE in front of the ranker
(``retrieve/``): a two-tower user encoder feeds a sharded MIPS top-k
index (int8 codes on the embedding-shard substrate — riding the
``--serve-shards`` tier when one exists, or ``--retrieve-shards M``
standalone index shards otherwise), and ``/predict`` answers USER
requests — retrieve ``--retrieve-k`` candidates under
``--retrieve-deadline-ms``, rank them through the engine/fleet with
the remaining ``--serve-deadline-ms`` budget, and return the re-ranked
candidate ids. ``POST /retrieve`` exposes the index stage alone. A
dead index shard DROPS its candidates (``"degraded": true`` — never
fabricated ids, never a failed request). Cascade mode needs the
in-process transport (``--serve-transport tcp`` / ``--serve-shard-procs``
are rejected at startup).

No framework webserver: a stdlib ``http.server`` ThreadingHTTPServer is
all the engine needs — every handler thread just submits into the
engine's queue and blocks on its future, the batcher coalesces across
handler threads.

  # terminal 1: train, publishing snapshots
  python examples/native/dlrm.py --checkpoint-dir /tmp/dlrm-ckpt --save-every 50

  # terminal 2: serve them, hot-reloading as they land (2 replicas)
  python examples/native/serve_dlrm.py --checkpoint-dir /tmp/dlrm-ckpt \\
      --serve-replicas 2 --serve-max-batch 64 --port 8000

  curl -s localhost:8000/healthz
  curl -s localhost:8000/stats
  curl -s -X POST localhost:8000/predict -d \\
      '{"dense": [[0.1, 0.2, 0.3, 0.4]], "sparse": [[[1],[2],[3],[4]]]}'

Endpoints:
  POST /predict  {"dense": [...], "sparse": [...]}  ->
                 {"scores": [...], "version": N, "latency_ms": ...}
                 429 on Overloaded, 504 on DeadlineExceeded,
                 503 when no replica can take the request
                 (--retrieve on: the same request describes a USER;
                 the response adds "candidates" — re-ranked item ids —
                 plus "retrieve_versions", "stage_ms", and the OR'd
                 "degraded" flag)
  POST /retrieve {"dense": [...], "sparse": [...][, "k": N]}  ->
                 {"ids": [[...]], "scores": [[...]], "versions": ...,
                 "degraded": ..., "latency_ms": ...} — the retrieve
                 stage alone (--retrieve on only; 404 otherwise)
  GET  /stats    engine stats() — or fleet-wide router stats() with
                 per-replica circuit-breaker state in fleet mode
  GET  /healthz  200 {"ok": true, ...} while the engine (fleet: at
                 least one healthy replica) is accepting requests;
                 503 {"ok": false, ...} when the queue is saturated,
                 the server is draining, or the batcher died — load
                 balancers must stop sending traffic HERE, not learn
                 it from request errors
  GET  /metrics  Prometheus text exposition of the obs registry
                 (``--obs on``; with obs off the body is a comment
                 saying so) — request/latency/reload series from the
                 engine, router, watcher, and shard tier
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import dlrm_flexflow_tpu as ff
from dlrm_flexflow_tpu.models.dlrm import DLRMConfig, build_dlrm
from dlrm_flexflow_tpu.serve import (DeadlineExceeded, FleetUnavailable,
                                     Overloaded)
from dlrm_flexflow_tpu.utils.logging import get_logger

log_app = get_logger("serve_dlrm")


def build_server_model(cfg, dcfg, mesh=None):
    """Same graph as the trainer (fingerprints must match for hot
    reload); compiled at the largest serve bucket so every bucket pads
    under the compile batch. ``mesh`` pins a fleet replica to its own
    device slice."""
    model = ff.FFModel(cfg)
    build_dlrm(model, dcfg)
    model.compile(ff.SGDOptimizer(lr=cfg.learning_rate),
                  "mean_squared_error", ["mse"], mesh=mesh)
    model.init_layers()
    return model


def make_handler(serve, input_names, cascade=None):
    """``serve`` is an InferenceEngine or a FleetRouter — both expose
    predict()/stats()/healthz() with the same contract. ``cascade``
    (a retrieve.CascadeEngine) switches /predict into cascade mode and
    opens POST /retrieve."""
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        def _reply(self, code, payload):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _reply_text(self, code, text,
                        ctype="text/plain; version=0.0.4"):
            body = text.encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):   # route through our logger
            log_app.debug(fmt, *args)

        def do_GET(self):
            if self.path == "/healthz":
                hz = serve.healthz()
                # 503 tells the balancer to stop routing here while the
                # queue is saturated or the server is draining; a 200
                # with ok:false would keep the traffic coming
                self._reply(200 if hz["ok"] else 503, hz)
            elif self.path == "/stats":
                st = serve.stats()
                if cascade is not None:
                    st = dict(st)
                    st["cascade"] = cascade.stats()
                self._reply(200, st)
            elif self.path == "/metrics":
                # Prometheus text exposition of the obs registry; with
                # --obs off the registry holds no instruments, so the
                # body is a self-explaining comment instead of silence
                from dlrm_flexflow_tpu.obs import metrics as obsm
                if obsm.enabled():
                    self._reply_text(200,
                                     obsm.registry().prometheus_text())
                else:
                    self._reply_text(
                        200, "# observability is off — restart with "
                             "--obs on to populate this endpoint\n")
            else:
                self._reply(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if self.path not in ("/predict", "/retrieve"):
                self._reply(404, {"error": f"no route {self.path}"})
                return
            if self.path == "/retrieve" and cascade is None:
                self._reply(404, {"error": "retrieval is off — restart "
                                           "with --retrieve on"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                feats = {}
                for name in input_names:
                    if name not in req:
                        raise ValueError(f"missing input {name!r}")
                    arr = np.asarray(req[name])
                    feats[name] = (arr.astype(np.int32)
                                   if name == "sparse"
                                   else arr.astype(np.float32))
            except (ValueError, json.JSONDecodeError) as e:
                self._reply(400, {"error": str(e)})
                return
            try:
                if self.path == "/retrieve":
                    k = int(req.get("k", cascade.config.k))
                    r = cascade.index.topk(
                        cascade.user_encoder(feats), k,
                        deadline_s=cascade.config.retrieve_deadline_ms
                        / 1e3)
                    self._reply(200, {
                        "ids": r.ids.tolist(),
                        "scores": r.scores.tolist(),
                        "versions": {str(s): int(v)
                                     for s, v in r.versions.items()},
                        "degraded": bool(r.degraded),
                        "dropped_slots": list(r.dropped_slots),
                        "latency_ms": round(r.latency_ms, 3)})
                    return
                if cascade is not None:
                    cp = cascade.predict(feats)
                    body = {
                        "candidates": cp.ids.tolist(),
                        "scores": cp.scores.tolist(),
                        "version": cp.rank_version,
                        "retrieve_versions": {
                            str(s): int(v)
                            for s, v in cp.retrieve_versions.items()},
                        "degraded": bool(cp.degraded),
                        "latency_ms": round(cp.latency_ms, 3),
                        "stage_ms": {s: round(v, 3)
                                     for s, v in cp.stage_ms.items()}}
                    if cp.rank_versions is not None:
                        body["versions"] = {
                            str(s): int(v)
                            for s, v in cp.rank_versions.items()}
                    self._reply(200, body)
                    return
                pred = serve.predict(feats)
                body = {
                    "scores": np.asarray(pred.scores).reshape(-1).tolist(),
                    "version": pred.version,
                    "latency_ms": round(pred.latency_ms, 3)}
                versions = getattr(pred, "versions", None)
                if versions is not None:
                    # sharded tier: the per-shard version vector this
                    # answer read, plus the degraded flag (default-row
                    # answers are honest about being approximate)
                    body["versions"] = {str(k): int(v)
                                        for k, v in versions.items()}
                    body["degraded"] = bool(getattr(pred, "degraded",
                                                    False))
                self._reply(200, body)
            except Overloaded as e:
                self._reply(429, {"error": str(e)})
            except FleetUnavailable as e:
                self._reply(503, {"error": str(e)})
            except (DeadlineExceeded, TimeoutError) as e:
                self._reply(504, {"error": str(e)})
            except ValueError as e:
                self._reply(400, {"error": str(e)})
            except Exception as e:   # noqa: BLE001 — e.g. a shape that
                # passed coercion but failed inside the dispatch; an
                # uncaught handler exception would DROP the connection
                # (no status at all) instead of answering 500
                log_app.exception("predict failed")
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})

    return Handler


def _replica_mesh(i, n):
    """Replica i's device slice: the local devices split n ways (each
    replica MUST own its own mesh — replicas sharing devices would
    serialize, and on CPU can deadlock concurrent collectives)."""
    import jax

    from dlrm_flexflow_tpu.parallel.mesh import make_mesh
    devs = jax.devices()
    per = max(1, len(devs) // n)
    lo = (i * per) % len(devs)
    return make_mesh(devices=devs[lo:lo + per])


def _shard_cache_dir(cfg, ckpt_dir):
    from dlrm_flexflow_tpu.utils.warmcache import cache_dir_for
    return cache_dir_for(ckpt_dir,
                         getattr(cfg, "compile_cache_dir", ""))


_SHARD_PROCS = []  # child shard-server processes, reaped in main()


def _wants_shard_tier(cfg):
    return (int(getattr(cfg, "serve_shards", 0)) > 0
            or int(getattr(cfg, "serve_shard_procs", 0)) > 0)


def _spawn_shard_procs(cfg, model, ckpt_dir):
    """The tcp path: seed the warm shard cache from the ranker's model,
    spawn one ``serve/shard_server.py`` OS process per slot, and connect
    ``RemoteShard`` clients over the wire protocol. The child processes
    land in ``_SHARD_PROCS`` for shutdown."""
    import subprocess
    n_shards = int(getattr(cfg, "serve_shard_procs", 0))
    tier_cfg = ff.ShardTierConfig.from_config(cfg)
    cache_dir = _shard_cache_dir(cfg, ckpt_dir)
    if not cache_dir:
        raise SystemExit(
            "--serve-shard-procs needs a shard cache directory to boot "
            "the child processes from — set --checkpoint-dir or "
            "--compile-cache-dir")
    ff.EmbeddingShardSet.seed_shard_cache(model, n_shards, cache_dir,
                                          config=tier_cfg)
    repo_root = os.path.join(os.path.dirname(__file__), "..", "..")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (os.path.abspath(repo_root),
                    env.get("PYTHONPATH", "")) if p)
    addresses = []
    for slot in range(n_shards):
        proc = subprocess.Popen(
            [sys.executable, "-m", "dlrm_flexflow_tpu.serve.shard_server",
             "--cache-dir", cache_dir, "--nshards", str(n_shards),
             "--slot", str(slot), "--port", "0"],
            stdout=subprocess.PIPE, text=True, env=env)
        _SHARD_PROCS.append(proc)
        line = proc.stdout.readline().strip()
        if not line.startswith("SHARD_SERVER_OK"):
            raise SystemExit(
                f"shard server slot {slot} failed to boot "
                f"(got {line!r}, exit={proc.poll()})")
        port = int(dict(kv.split("=", 1)
                        for kv in line.split()[1:])["port"])
        addresses.append(("127.0.0.1", port))
        log_app.info("shard process slot %d up: pid=%d port=%d",
                     slot, proc.pid, port)
    return ff.EmbeddingShardSet.connect(addresses, config=tier_cfg,
                                        cache_dir=cache_dir)


def _stop_shard_procs():
    for proc in _SHARD_PROCS:
        if proc.poll() is None:
            proc.terminate()
    for proc in _SHARD_PROCS:
        try:
            proc.wait(timeout=5)
        except Exception:
            proc.kill()
    _SHARD_PROCS.clear()


def _build_shard_set(cfg, model, ckpt_dir):
    """Row-shard the model's host tables into the lookup tier and
    release the ranker's own copies (the point of the split)."""
    n_procs = int(getattr(cfg, "serve_shard_procs", 0))
    transport = str(getattr(cfg, "serve_transport", "inproc"))
    if n_procs > 0 and transport != "tcp":
        raise SystemExit(
            "--serve-shard-procs requires --serve-transport tcp "
            "(separate processes cannot share in-process method calls)")
    if n_procs > 0:
        shard_set = _spawn_shard_procs(cfg, model, ckpt_dir)
        n_shards = n_procs
    else:
        n_shards = int(getattr(cfg, "serve_shards", 0))
        shard_set = ff.EmbeddingShardSet.build(
            model, n_shards, config=ff.ShardTierConfig.from_config(cfg),
            cache_dir=_shard_cache_dir(cfg, ckpt_dir))
    freed = ff.EmbeddingShardSet.release_ranker_tables(model)
    log_app.info(
        "sharded serving tier: %d lookup shard(s) [%s], ranker released "
        "%.1f MB of tables", n_shards,
        "tcp, separate processes" if n_procs > 0 else "inproc",
        freed / 1e6)
    return shard_set


def _validate_retrieve(cfg):
    """Reject knob combinations the cascade cannot honor — at startup,
    with the knob names in the message, not as a mid-request surprise."""
    on = str(getattr(cfg, "retrieve", "off")) == "on"
    rshards = int(getattr(cfg, "retrieve_shards", 0))
    if not on:
        if rshards > 0:
            raise SystemExit(
                "--retrieve-shards does nothing without --retrieve on — "
                "refusing to silently ignore it")
        return False
    if str(getattr(cfg, "serve_transport", "inproc")) != "inproc":
        raise SystemExit(
            "--retrieve on requires --serve-transport inproc: the "
            "cascade scores candidates through in-process shard calls "
            "(the wire path for retrieval is not plumbed yet)")
    if int(getattr(cfg, "serve_shard_procs", 0)) > 0:
        raise SystemExit(
            "--retrieve on is incompatible with --serve-shard-procs: "
            "the index attaches to in-process shards")
    nshards = int(getattr(cfg, "serve_shards", 0))
    if nshards > 0 and rshards not in (0, nshards):
        raise SystemExit(
            f"--retrieve-shards {rshards} conflicts with "
            f"--serve-shards {nshards}: with a sharded ranker tier the "
            f"index rides THOSE shards (pass 0, or match the count)")
    return True


def _build_cascade(cfg, dcfg, serve, shard_set):
    """Stand the retrieval stage up in front of the ranker: two-tower
    user/item heads sized to the DLRM's own inputs (so /predict's
    feature dict feeds both stages), the item catalog encoded and
    attached as the MIPS index — to the ranker's shard set when one
    exists, else to ``--retrieve-shards`` standalone index shards.
    Returns ``(CascadeEngine, owned_set_or_None)``."""
    from dlrm_flexflow_tpu.retrieve import (CascadeConfig, CascadeEngine,
                                            ShardedMIPSIndex,
                                            TwoTowerConfig,
                                            build_two_tower,
                                            dlrm_candidate_features,
                                            item_embeddings,
                                            transfer_tower_params)
    tcfg = TwoTowerConfig(
        n_items=int(dcfg.embedding_size[0]),
        dim=32,
        user_dense_dim=int(dcfg.mlp_bot[0]),
        user_embedding_size=list(dcfg.embedding_size),
        user_sparse_dim=8,
        user_bag_size=int(dcfg.embedding_bag_size))

    def build_head(head):
        m = ff.FFModel(cfg)
        build_two_tower(m, tcfg, head=head)
        m.compile(ff.SGDOptimizer(lr=cfg.learning_rate),
                  "mean_squared_error", ["mse"])
        m.init_layers()
        return m

    user_model = build_head("user")
    item_model = build_head("item")
    # keep the untrained heads CONSISTENT: both serve the same init the
    # way both serve the same snapshot after a real transfer (a trained
    # two-tower checkpoint would restore here, then transfer the same
    # way)
    transfer_tower_params(user_model, item_model)

    def encode(feats):
        dense = np.asarray(feats["dense"], np.float32)
        sparse = np.asarray(feats["sparse"], np.int32)
        B = user_model.config.batch_size
        n = dense.shape[0]
        out = np.empty((n, tcfg.dim), np.float32)
        for lo in range(0, n, B):
            hi = min(lo + B, n)
            pad = B - (hi - lo)
            d, s = dense[lo:hi], sparse[lo:hi]
            if pad:
                d = np.concatenate(
                    [d, np.zeros((pad,) + d.shape[1:], np.float32)])
                s = np.concatenate(
                    [s, np.zeros((pad,) + s.shape[1:], np.int32)])
            res = np.asarray(user_model.forward_batch(
                {"user_dense": d, "user_sparse": s}))
            out[lo:hi] = res[:hi - lo]
        return out

    item_emb = item_embeddings(item_model, tcfg)
    owned = None
    if shard_set is not None:
        index = ShardedMIPSIndex.build(shard_set, item_emb)
        where = f"riding the {shard_set.nshards}-shard ranker tier"
    else:
        m = max(1, int(getattr(cfg, "retrieve_shards", 0)))
        owned = ShardedMIPSIndex.standalone_set(m)
        index = ShardedMIPSIndex.build(owned, item_emb)
        where = f"{m} standalone index shard(s)"
    cascade = CascadeEngine(
        index, encode, serve,
        dlrm_candidate_features(len(dcfg.embedding_size),
                                list(dcfg.embedding_size)),
        CascadeConfig.from_config(cfg))
    log_app.info(
        "retrieval cascade on: %d-item index (%s), k=%d, retrieve "
        "deadline %.0f ms", index.n_items, where, cascade.config.k,
        cascade.config.retrieve_deadline_ms)
    return cascade, owned


def _build_fleet(cfg, dcfg, n, ckpt_dir):
    """N replicas on disjoint device slices behind a FleetRouter."""
    scfg = ff.ServeConfig.from_config(cfg)
    shard_holder = {}

    def factory(i):
        model = build_server_model(cfg, dcfg, mesh=_replica_mesh(i, n))
        if _wants_shard_tier(cfg):
            # the FIRST model built seeds the (single, shared) shard
            # set; every ranker — this one included — then releases its
            # own tables and resolves ids through the set
            if "set" not in shard_holder:
                shard_holder["set"] = _build_shard_set(cfg, model,
                                                       ckpt_dir)
            else:
                ff.EmbeddingShardSet.release_ranker_tables(model)
        return model

    fleet = ff.Fleet.build(factory, n, scfg, checkpoint_dir=ckpt_dir,
                           shard_set=None)
    if shard_holder:
        fleet.shard_set = shard_holder["set"]
        for rep in fleet:
            rep.engine.attach_shard_set(fleet.shard_set)
    if ckpt_dir:
        for rep in fleet:
            # initial restore through the watcher's READ-ONLY manifest
            # scan, resharding the trainer's mesh onto the replica's
            if ff.SnapshotWatcher(rep.engine, ckpt_dir,
                                  elastic=True).poll_once():
                log_app.info("replica %d serving snapshot version %d",
                             rep.rid, rep.engine.version)
            else:
                log_app.warning(
                    "replica %d: no restorable snapshot in %s — serving "
                    "fresh init until the trainer publishes one",
                    rep.rid, ckpt_dir)
    return ff.FleetRouter(fleet, ff.RouterConfig.from_config(cfg))


def main(argv=None):
    # same CPU-virtualization escape hatch as _common.setup (the axon
    # sitecustomize pins an accelerator plugin; FF_FORCE_CPU=<ndev>
    # virtualizes host devices explicitly for tests/CPU-only serving)
    force_cpu = int(os.environ.get("FF_FORCE_CPU") or 0)
    if force_cpu > 0:
        from dlrm_flexflow_tpu.utils.testing import ensure_cpu_devices
        ensure_cpu_devices(force_cpu)
    cfg = ff.FFConfig.parse_args(argv)
    # --obs on must land BEFORE any engine/fleet is built: instruments
    # resolve at creation time (no-op singletons once off stays off)
    from dlrm_flexflow_tpu import obs
    if obs.configure(cfg):
        log_app.info("observability on: GET /metrics serves the "
                     "registry%s",
                     f", traces export to {cfg.obs_trace_dir}"
                     if cfg.obs_trace_dir else "")
    dcfg = DLRMConfig.parse_args(cfg.unparsed)
    port = 8000
    rest = list(cfg.unparsed)
    if "--port" in rest:
        port = int(rest[rest.index("--port") + 1])

    ckpt_dir = cfg.checkpoint_dir or None
    n = int(getattr(cfg, "serve_replicas", 1))
    retrieve_on = _validate_retrieve(cfg)   # SystemExit on bad combos,
    #                                         BEFORE any model compiles
    shard_set = None
    if n > 1:
        serve = _build_fleet(cfg, dcfg, n, ckpt_dir)
        model = serve.fleet.replicas[0].engine.model
        shard_set = serve.fleet.shard_set
    else:
        model = build_server_model(cfg, dcfg)
        if _wants_shard_tier(cfg):
            shard_set = _build_shard_set(cfg, model, ckpt_dir)
        serve = ff.InferenceEngine(model, checkpoint_dir=ckpt_dir,
                                   shard_set=shard_set)
        if ckpt_dir:
            # initial load through the watcher's READ-ONLY manifest
            # scan (a CheckpointManager here would sweep tmp files
            # under a live trainer) — params_only restore of the newest
            # valid snapshot
            if ff.SnapshotWatcher(serve, ckpt_dir).poll_once():
                log_app.info("serving snapshot version %d", serve.version)
            else:
                log_app.warning(
                    "no restorable snapshot in %s — serving fresh init "
                    "until the trainer publishes one", ckpt_dir)
    input_names = [t.name for t in model.input_tensors]

    cascade = cascade_set = None
    if retrieve_on:
        cascade, cascade_set = _build_cascade(cfg, dcfg, serve,
                                              shard_set)

    # SLO-driven autoscaling over the fleet (--serve-slo-ms + the
    # min/max replica bounds): grows on sustained p99/queue pressure,
    # replaces dead replicas, shrinks when idle. Fleet mode only — a
    # single engine has nothing to grow.
    scaler = None
    if n > 1 and float(getattr(cfg, "serve_slo_ms", 0.0)) > 0:
        scaler = ff.Autoscaler(serve, ff.AutoscaleConfig.from_config(cfg))
        log_app.info(
            "autoscaler on: SLO %.0f ms, %d..%d replicas",
            cfg.serve_slo_ms, cfg.serve_min_replicas,
            cfg.serve_max_replicas)
    if shard_set is not None and scaler is None:
        # no autoscaler to drive shard health ticks — the set runs its
        # own probe/replace loop so an ejected shard still heals
        shard_set.start_health()

    from http.server import ThreadingHTTPServer
    with serve:
        if scaler is not None:
            scaler.start()
        httpd = ThreadingHTTPServer(
            ("0.0.0.0", port),
            make_handler(serve, input_names, cascade=cascade))
        log_app.info(
            "serving DLRM on :%d (%s%s)", port,
            f"{n} replicas" if n > 1 else
            f"buckets {serve.stats()['buckets']}",
            f", hot-reload from {ckpt_dir}" if ckpt_dir else "")
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            if scaler is not None:
                scaler.close()
            if shard_set is not None:
                shard_set.stop_health()
                shard_set.close()
            if cascade_set is not None:
                cascade_set.close()
            _stop_shard_procs()
            httpd.server_close()
            from dlrm_flexflow_tpu.obs import trace as obstrace
            path = obstrace.export_to_dir()
            if path:
                log_app.info("exported serving trace to %s", path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
