#!/bin/bash
# Reference run_criteo_kaggle.sh:1-8 shapes: 26 tables x 16-d, bot MLP
# 13-512-256-64-16, top MLP 224-512-256-1, batch 256/device.
# Pass --data-path criteo.npz (from tools/preprocess_criteo.py) for real data.
ndev=${NDEV:-$(python -c 'import jax; print(len(jax.devices()))')}
python "$(dirname "$0")/dlrm.py" \
    -ll:gpu "$ndev" -b $((256 * ndev)) -e 1 \
    --arch-embedding-size 1396-550-2481689-687-20-15-204-96-14-1400181-397059-3166985-10-2208-11156-155-4-976-14-1398149-1263872-1246444-13107-336-101-30 \
    --arch-sparse-feature-size 16 \
    --arch-mlp-bot 13-512-256-64-16 \
    --arch-mlp-top 224-512-256-1 \
    "$@"
