#!/usr/bin/env python
"""InceptionV3 on synthetic images (reference:
examples/cpp/InceptionV3/inception.cc).

  python examples/native/inception_v3.py -b 32 -e 1
"""

import sys

from _common import ff, setup, synthetic_classification, train
from dlrm_flexflow_tpu.models.inception import build_inception_v3


def main(argv=None):
    cfg, mesh = setup(argv if argv is not None else sys.argv[1:],
                      default_batch=32)
    model = ff.FFModel(cfg)
    inputs, _ = build_inception_v3(model, num_classes=1000, image_hw=299)
    x, y = synthetic_classification(inputs, 1000, 2 * cfg.batch_size,
                                    seed=cfg.seed)
    train(model, x, y, cfg, mesh=mesh)


if __name__ == "__main__":
    main()
